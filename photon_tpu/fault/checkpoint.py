"""Preemption-safe checkpoint/resume for GAME coordinate descent.

The reference's recovery story is Spark lineage plus per-iteration HDFS
model dumps; a preempted TPU slice has neither.  This module snapshots the
full descent state after every outer iteration — per-coordinate models,
the residual engine's score rows (fetched once, off the hot path), the
best-model-so-far, validation-metric history, and the iteration/quarantine
counters — into a versioned on-disk checkpoint published with the atomic
protocol of :mod:`photon_tpu.fault.atomic`:

    <dir>/ckpt-000002/
        state.json      # iteration, history, best metrics, fingerprint
        arrays.npz      # model tables + residual score rows (exact dtypes)
        manifest.json   # content hashes, written last
    <dir>/LATEST        # pointer file, replaced atomically

Resume rebuilds the device score tables from the snapshot rows and warm
starts every coordinate from its checkpointed model, so a resumed fit is
numerically identical to an uninterrupted one (score rows round-trip at
their native dtype: f32 for the device engine, f64 for the host escape
hatch).  Under multi-controller runs every rank LOADS the checkpoint (the
directory must be on storage all ranks can read) but only rank 0 WRITES —
the same primary-writes rule the drivers use for models and reports.

Async publishing (``PHOTON_CHECKPOINT_ASYNC`` / ``--checkpoint-async``,
default on): the per-iteration snapshot is split into a cheap STAGING step
on the descent thread — ``copy_to_host_async()`` starts the d2h copies of
every score row and model table together, then gathers them (the transfers
overlap in flight instead of fetching serially) — and the expensive
serialize + fsync + atomic-rename publish, which runs on a dedicated
publisher thread with bounded depth 1.  The training loop blocks only when
the PREVIOUS publish is still in flight (``checkpoint.blocked_s``); a
publish failure is re-raised at the next save (or the final drain) — never
swallowed; and the final iteration drains the publisher before the fit
returns, so a completed run always ends with its last checkpoint published.
Durability window: under async publishing ``LATEST`` may lag the training
loop by one iteration — a kill can lose at most the single snapshot that
was still in flight (the previous published checkpoint stays intact; the
same atomic temp+fsync+rename protocol runs on the publisher thread, and
the ``checkpoint:stage`` / ``checkpoint:write`` fault sites keep firing
inside its staging and torn-write windows).

Mesh-shape portability (elastic resume): a checkpoint records only the
LOGICAL layout of the fit — unpadded row counts, per-coordinate entity
vocabularies and dimensions (the ``layout`` payload section, digested into
the manifest) — never the mesh shape that wrote it.  Score rows are
snapshotted trimmed to the logical length, model tables at their logical
``[entities, dim]`` shape, and every padded/sharded device buffer is
rebuilt at load time against the RESUMING run's mesh
(:func:`photon_tpu.parallel.mesh.reshard_to_mesh` and the engines'
``load_rows``).  The compatibility fingerprint pins the logical layout and
deliberately contains NO device-, process-, or mesh-shape component — so a
fit written on N processes/devices resumes on M (preemptible capacity,
mid-sweep mesh resizes), and the resumed state is bit-identical to the
saved one.

Host-side RSS bound: the async publisher holds one in-flight snapshot's
staged host copies.  ``checkpoint.staged_bytes`` gauges that residency,
and ``max_staged_mb`` (``--checkpoint-max-staged-mb``;
``PHOTON_CHECKPOINT_MAX_STAGED_MB``) caps it — a snapshot over the cap
publishes BLOCKING on the loop thread (``checkpoint.staged_fallback_sync``
counts the fallbacks) instead of holding a second GB-scale snapshot while
the loop runs ahead.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from photon_tpu.fault.atomic import (
    atomic_dir,
    atomic_write_bytes,
    verify_manifest,
    write_manifest,
)
from photon_tpu.fault.injection import fault_point
from photon_tpu.fault.retry import retry_call
from photon_tpu.telemetry import NULL_SESSION

STATE_VERSION = 1
LATEST_NAME = "LATEST"


class CheckpointError(RuntimeError):
    """A checkpoint could not be loaded (missing, corrupt, or mismatched)."""


def resolve_checkpoint_async(mode=None) -> bool:
    """Resolve the checkpoint-publishing mode: True = async publisher.

    Precedence: explicit ``mode`` (driver flag / bool) over the
    ``PHOTON_CHECKPOINT_ASYNC`` env var over the default (``on``): the
    async publisher is the steady state, synchronous publishing is the
    escape hatch (``--checkpoint-async off``) for storage that misbehaves
    under concurrent writers."""
    if isinstance(mode, bool):
        return mode
    resolved = (
        (mode or "").strip().lower()
        or os.environ.get("PHOTON_CHECKPOINT_ASYNC", "").strip().lower()
        or "on"
    )
    if resolved not in ("on", "off"):
        raise ValueError(
            f"checkpoint-async must be 'on' or 'off', got {resolved!r}"
        )
    return resolved == "on"


def has_published_checkpoint(checkpoint_dir: Optional[str]) -> bool:
    """True when any checkpoint chain under ``checkpoint_dir`` has a
    PUBLISHED version (a LATEST pointer exists) — .tmp-* debris from a run
    killed before its first publish does not count."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return False
    for _dirpath, _dirnames, filenames in os.walk(checkpoint_dir):
        if LATEST_NAME in filenames:
            return True
    return False


def stage_to_host(arrays: Dict[str, object], telemetry=None) -> Dict[str, np.ndarray]:
    """Two-pass d2h staging of a checkpoint's array dict.

    First pass starts ``copy_to_host_async()`` on every device leaf — all
    the transfers go in flight together; second pass gathers them into
    numpy (each gather blocks only on a copy that is already running).
    Host leaves pass straight through.  The gathered bytes are counted as
    ``descent.host_transfer_bytes{path=checkpoint}`` — the sanctioned
    off-hot-path fetch."""
    import jax

    for value in arrays.values():
        if isinstance(value, jax.Array) and value.is_fully_addressable:
            try:
                value.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass  # backends without async d2h fall back to the gather
    staged: Dict[str, np.ndarray] = {}
    d2h_bytes = 0
    for key, value in arrays.items():
        if isinstance(value, jax.Array):
            from photon_tpu.parallel.mesh import to_host

            # host-sync: checkpoint staging — the async copies above put
            # these transfers in flight; this gather is the sanctioned
            # once-per-iteration off-hot-path fetch.
            host = to_host(value)
            d2h_bytes += host.nbytes
        else:
            # host-sync: host leaves (host-engine rows, key vocabularies)
            # normalize through numpy without touching a device.
            host = np.asarray(value)
        staged[key] = host
    if telemetry is not None and d2h_bytes:
        telemetry.counter(
            "descent.host_transfer_bytes", direction="d2h", path="checkpoint"
        ).inc(d2h_bytes)
    return staged


class AsyncPublisher:
    """Dedicated checkpoint-publisher thread with bounded depth 1.

    ``submit(fn)`` first waits out any in-flight publish (the wait is the
    ONLY place the training loop can block on checkpoint IO —
    ``checkpoint.blocked_s`` observes it) and re-raises a previous publish
    failure at the submission site: a failed publish surfaces on the next
    iteration, never silently.  ``drain()`` is the final-iteration barrier —
    it waits for the in-flight publish, stops the thread, and raises any
    pending failure.  ``checkpoint.publish_lag_s`` observes enqueue→landed
    latency per publish."""

    def __init__(self, telemetry=None, name: str = "checkpoint-publisher"):
        self.telemetry = telemetry or NULL_SESSION
        self._name = name
        self._job = None
        self._job_ready = threading.Condition()
        self._idle = threading.Event()
        self._idle.set()
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = False

    # -- worker --------------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._job_ready:
                while self._job is None and not self._stop:
                    self._job_ready.wait()
                if self._stop and self._job is None:
                    return
                fn, enqueued = self._job
                self._job = None
            try:
                with self.telemetry.span("checkpoint.publish"):
                    fn()
            except BaseException as e:  # surfaced at the next save/drain
                self._error = e
            finally:
                self.telemetry.histogram("checkpoint.publish_lag_s").observe(
                    time.monotonic() - enqueued
                )
                self._idle.set()

    def _wait_idle(self) -> None:
        t0 = time.monotonic()
        self._idle.wait()
        self.telemetry.histogram("checkpoint.blocked_s").observe(
            time.monotonic() - t0
        )

    def _raise_pending(self) -> None:
        err, self._error = self._error, None
        if err is not None:
            raise err

    # -- API -----------------------------------------------------------------
    def submit(self, fn) -> None:
        """Enqueue one publish; blocks while the previous one is in flight
        (bounded depth 1) and re-raises its failure here."""
        self._wait_idle()
        self._raise_pending()
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, name=self._name, daemon=True
            )
            self._thread.start()
        self._idle.clear()
        with self._job_ready:
            self._job = (fn, time.monotonic())
            self._job_ready.notify()

    def wait(self, reraise: bool = True) -> None:
        """Block until the in-flight publish (if any) lands, WITHOUT
        stopping the thread — the blocking-save fallback's barrier (the
        staged-bytes cap publishes synchronously but keeps the publisher
        alive for later, smaller snapshots)."""
        self._idle.wait()
        if reraise:
            self._raise_pending()

    def drain(self, reraise: bool = True) -> None:
        """Wait out the in-flight publish and stop the thread.  With
        ``reraise`` (the final-iteration barrier) a pending publish failure
        propagates; ``reraise=False`` (error paths) preserves the caller's
        original exception while still quiescing the publisher."""
        self._idle.wait()
        thread = self._thread
        if thread is not None and thread.is_alive():
            with self._job_ready:
                self._stop = True
                self._job_ready.notify()
            thread.join()
        self._thread = None
        if reraise:
            self._raise_pending()
        else:
            self._error = None


def logical_layout(num_examples: int, coordinate_kinds=None) -> dict:
    """The MESH-INDEPENDENT layout of a descent run: logical (unpadded)
    training row count plus each coordinate's kind in update order.  This —
    not any padded shape, shard count, or device count — is what a
    checkpoint pins: padding and sharding are derived from whatever mesh
    the resuming run constructs (reshard_to_mesh)."""
    return {
        "rows": int(num_examples),
        "coordinates": {
            str(name): str(kind)
            for name, kind in (coordinate_kinds or {}).items()
        },
    }


def layout_digest(layout: dict) -> str:
    """Stable digest of a logical layout.  Stamped into the checkpoint
    manifest so tools (and operators) can identify a checkpoint's logical
    shape without opening ``arrays.npz``; the descent load path
    cross-checks it against the payload's layout, so the two can never
    silently disagree (mixed-version artifacts, writer bugs)."""
    import hashlib

    return hashlib.sha256(
        json.dumps(layout, sort_keys=True).encode()
    ).hexdigest()[:16]


def descent_fingerprint(
    task_type: str, coordinate_names, num_examples: int, residual_mode: str,
    config_key: Optional[str] = None,
    validation_key: Optional[str] = None,
    locked=(),
    warm_start: bool = False,
    coordinate_kinds=None,
) -> dict:
    """The ONE definition of a descent run's checkpoint-compatibility
    fingerprint (descent and estimator both check against it): a resumed
    run must be the same descent — same task, coordinate update sequence,
    LOGICAL layout (row count + per-coordinate kinds, via
    :func:`logical_layout`), residual mode, optimization configuration
    (when the caller supplies a key), validation setup (primary evaluator,
    or None for an unevaluated fit), lock list, and warm-start-ness — or
    the restored state would silently be another run's model (or crash on
    a best-metrics shape it never tracked).

    Deliberately ABSENT: any device-count-, process-count-, or mesh-shape-
    dependent component.  Mesh shape is an execution choice, not an
    identity of the fit — dropping it from the fingerprint is what makes
    checkpoints elastic (a fit written on N devices resumes on M; the
    padded/sharded buffers are rebuilt for the resuming mesh at load)."""
    fp = {
        "task_type": task_type,
        "coordinates": list(coordinate_names),
        "layout": logical_layout(num_examples, coordinate_kinds),
        "residual_mode": residual_mode,
        "validation": validation_key,
        "locked": sorted(locked),
        "warm_start": bool(warm_start),
    }
    if config_key is not None:
        fp["config"] = config_key
    return fp


def require_fingerprint(state, expected: dict, what: str):
    """The ONE refusal: pass ``state`` through unless its fingerprint
    differs from ``expected``, in which case raise :class:`CheckpointError`
    naming ``what`` the checkpoint failed to match.  ``state`` may be None
    (nothing checkpointed yet — auto resume starts fresh)."""
    if state is not None and state.fingerprint != expected:
        raise CheckpointError(
            f"checkpoint fingerprint {state.fingerprint} does not match "
            f"{what} ({expected}); refusing to resume"
        )
    return state


def configuration_key(coordinate_configs: dict) -> str:
    """Digest of a sweep point's per-coordinate optimization configs
    (regularization weights, solver settings — frozen-dataclass reprs are
    deterministic and content-bearing).  Deliberately EXCLUDES
    ``descent_iterations``: resuming with more iterations is a supported
    continuation, a different regularization is a different model."""
    import hashlib

    return hashlib.sha256(repr(coordinate_configs).encode()).hexdigest()[:16]


@dataclasses.dataclass
class DescentState:
    """One outer iteration's complete restart state (live model objects;
    (de)serialization to arrays happens in the checkpointer).

    ``residual_rows`` may hold host numpy rows (the host engine, or a
    pre-fetched sync snapshot) or DEVICE row handles (the async staging
    path) — the checkpointer's :func:`stage_to_host` gathers either."""

    iteration: int              # last COMPLETED outer iteration
    num_iterations: int         # the run's target iteration count
    task_type: str
    models: Dict[str, object]
    best_models: Dict[str, object]
    best_metrics: Dict[str, float]
    best_iteration: int
    history: List[dict]
    residual_rows: Dict[str, np.ndarray]
    quarantined: int
    fingerprint: dict
    # Streamed (out-of-core) descents only: the mid-epoch restart cursor —
    # {"chunk_rows", "cursor" (coordinates completed in the in-progress
    # iteration; 0 = iteration boundary), "seq" (monotonic checkpoint
    # sequence), "tile_digests" (per-chunk score-tile content digests,
    # verified on resume)}.  None for resident descents.
    stream: Optional[dict] = None

    @property
    def completed(self) -> bool:
        return self.iteration + 1 >= self.num_iterations and not (
            self.stream or {}
        ).get("cursor")


# -- model <-> array serialization ------------------------------------------


def _models_to_arrays(prefix: str, models: Dict[str, object]):
    """(arrays, meta) for one model dict; array keys are
    ``<prefix><i>__<field>`` (npz-safe, order = meta order).  Device arrays
    are returned AS DEVICE HANDLES — :func:`stage_to_host` fetches them in
    one overlapped staging pass, not one blocking fetch per table."""
    from photon_tpu.game.model import FixedEffectModel, RandomEffectModel

    arrays, meta = {}, []
    for i, (name, model) in enumerate(models.items()):
        key = f"{prefix}{i}__"
        if isinstance(model, FixedEffectModel):
            coeff = model.coefficients
            arrays[key + "means"] = coeff.means
            if coeff.variances is not None:
                arrays[key + "variances"] = coeff.variances
            meta.append({
                "name": name, "kind": "fixed", "shard_name": model.shard_name,
                "has_variances": coeff.variances is not None,
            })
        elif isinstance(model, RandomEffectModel):
            arrays[key + "table"] = model.table
            # host-sync: entity-key vocabularies already live on host.
            arrays[key + "keys"] = np.asarray(model.keys)
            if model.variances is not None:
                arrays[key + "variances"] = model.variances
            meta.append({
                "name": name, "kind": "random", "shard_name": model.shard_name,
                "entity_column": model.entity_column,
                "has_variances": model.variances is not None,
            })
        else:
            raise TypeError(f"cannot checkpoint coordinate model {type(model)!r}")
    return arrays, meta


def _models_from_arrays(prefix: str, meta: List[dict], arrays, task_type: str,
                        mesh=None):
    """Rebuild coordinate models from checkpointed host arrays.

    Tables come back at their LOGICAL ``[entities, dim]`` shapes; with a
    ``mesh`` they are placed replicated over it (the SPMD-correct placement
    for model state every shard reads whole — the elastic-resume leg: the
    mesh here is the RESUMING run's, any shape), single-device otherwise.
    Bulk per-row state (score rows) is re-padded/re-sharded separately by
    the engines' ``load_rows``."""
    from photon_tpu.game.model import FixedEffectModel, RandomEffectModel
    from photon_tpu.models.glm import Coefficients, model_for_task
    from photon_tpu.parallel.mesh import put_replicated

    def place(host):
        return put_replicated(jnp.asarray(host), mesh)

    models = {}
    for i, m in enumerate(meta):
        key = f"{prefix}{i}__"
        variances = (
            place(arrays[key + "variances"]) if m["has_variances"] else None
        )
        if m["kind"] == "fixed":
            glm = model_for_task(
                task_type,
                Coefficients(place(arrays[key + "means"]), variances),
            )
            models[m["name"]] = FixedEffectModel(
                model=glm, shard_name=m["shard_name"]
            )
        else:
            models[m["name"]] = RandomEffectModel(
                table=place(arrays[key + "table"]),
                # host-sync: checkpointed key vocabularies are host data.
                keys=np.asarray(arrays[key + "keys"]),
                entity_column=m["entity_column"],
                shard_name=m["shard_name"],
                task_type=task_type,
                variances=variances,
            )
    return models


class CheckpointPublisherBase:
    """Shared checkpoint publication machinery: versioned directories under
    one root, the atomic temp+fsync+rename protocol with a manifest written
    last, a LATEST pointer, keep-N pruning, rank-0-writes — and the sync or
    async publish path.  :class:`DescentCheckpointer` (GAME descent state)
    and :class:`StreamCheckpointer` (streamed-GLM L-BFGS state) both
    publish through it.

    ``write`` defaults to ``jax.process_index() == 0`` at save time
    (rank-0-writes); every rank may load.  ``keep`` bounds on-disk versions
    (older checkpoints are pruned after a successful publish).
    ``async_publish`` (default: :func:`resolve_checkpoint_async`) routes
    publishes through a dedicated :class:`AsyncPublisher` thread.
    ``max_staged_mb`` (default ``PHOTON_CHECKPOINT_MAX_STAGED_MB``, else
    unbounded) caps the host RSS the async path may hold in staged
    snapshot copies: a snapshot over the cap publishes BLOCKING instead.
    """

    def __init__(self, directory: str, telemetry=None, logger=None,
                 keep: int = 2, write: Optional[bool] = None,
                 async_publish=None, max_staged_mb: Optional[float] = None):
        self.directory = directory
        self.telemetry = telemetry or NULL_SESSION
        self.logger = logger
        self.keep = max(1, keep)
        self._write = write
        self.async_publish = resolve_checkpoint_async(async_publish)
        self._publisher = (
            AsyncPublisher(self.telemetry) if self.async_publish else None
        )
        if max_staged_mb is None:
            raw = os.environ.get(
                "PHOTON_CHECKPOINT_MAX_STAGED_MB", ""
            ).strip()
            try:
                max_staged_mb = float(raw) if raw else None
            except ValueError:
                max_staged_mb = None
        self.max_staged_bytes = (
            None if max_staged_mb is None or max_staged_mb < 0
            else int(max_staged_mb * (1 << 20))
        )

    # -- helpers -------------------------------------------------------------
    def _should_write(self) -> bool:
        if self._write is not None:
            return self._write
        import jax

        return jax.process_index() == 0

    def _ckpt_name(self, iteration: int) -> str:
        return f"ckpt-{iteration:06d}"

    def latest_path(self) -> Optional[str]:
        """The checkpoint directory LATEST points to, or None."""
        pointer = os.path.join(self.directory, LATEST_NAME)
        if not os.path.isfile(pointer):
            return None
        with open(pointer) as f:
            name = f.read().strip()
        path = os.path.join(self.directory, name)
        return path if os.path.isdir(path) else None

    # -- save ----------------------------------------------------------------
    def save_arrays(self, iteration: int, arrays: Dict[str, object],
                    payload: dict) -> Optional[str]:
        """Stage + publish one checkpoint version; returns its final path
        (None on non-writing ranks).

        Staging (the overlapped d2h gather) always happens HERE, on the
        calling thread — device buffers may be donated or mutated the
        moment the training loop resumes, so the host copies must exist
        before this returns.  The publish (serialize + fsync + rename +
        prune) runs synchronously, or on the publisher thread when async:
        the call then blocks only if the PREVIOUS publish is still in
        flight, and a publish failure surfaces at the next save or the
        final :meth:`drain` — never silently.  Checkpoint IO retries like
        any other guarded write; an exhausted retry raises — a run that
        cannot checkpoint is a failed run, not a silently unprotected one."""
        if not self._should_write():
            return None
        t0 = time.monotonic()
        # The d2h-staging fault window: a kill here (or anywhere before the
        # publish rename) leaves the previously published chain untouched.
        fault_point("checkpoint:stage", iteration=iteration)
        staged = stage_to_host(arrays, telemetry=self.telemetry)
        staged_bytes = sum(a.nbytes for a in staged.values())
        # The async publisher's extra host residency is exactly one staged
        # snapshot (bounded depth 1): make it visible, and bound it.
        self.telemetry.gauge("checkpoint.staged_bytes").set(staged_bytes)
        final = os.path.join(self.directory, self._ckpt_name(iteration))

        def publish() -> str:
            return retry_call(
                lambda: self._publish_once(final, staged, payload),
                site="checkpoint:io",
                telemetry=self.telemetry, logger=self.logger,
            )

        if self._publisher is None:
            publish()
        elif (self.max_staged_bytes is not None
                and staged_bytes > self.max_staged_bytes):
            # Over the staged-RSS cap: publish BLOCKING on the loop thread
            # (after surfacing any previous in-flight failure) — the loop
            # pays the serialize+fsync wall clock, and the process never
            # holds this snapshot's host copies while running ahead.
            self._publisher.wait()
            self.telemetry.counter("checkpoint.staged_fallback_sync").inc()
            if self.logger is not None:
                self.logger.info(
                    "checkpoint: staged snapshot %.1f MB over the "
                    "--checkpoint-max-staged-mb cap (%.1f MB); publishing "
                    "blocking", staged_bytes / (1 << 20),
                    self.max_staged_bytes / (1 << 20),
                )
            publish()
        else:
            self._publisher.submit(publish)
        # In async mode this histogram observes the LOOP-SIDE cost (staging
        # + any wait on the previous publish) — the per-iteration premium
        # the descent actually pays; the publisher's own wall clock is
        # checkpoint.publish_lag_s.
        self.telemetry.histogram("checkpoint.write_seconds").observe(
            time.monotonic() - t0
        )
        self.telemetry.counter("checkpoint.saves").inc()
        if self.logger is not None:
            self.logger.info(
                "checkpoint: iteration %d -> %s%s", iteration, final,
                " (async publish)" if self._publisher is not None else "",
            )
        return final

    def drain(self, reraise: bool = True) -> None:
        """Final-iteration barrier: wait for the in-flight async publish
        (no-op in sync mode) and surface its failure.  ``reraise=False``
        quiesces the publisher on error paths without masking the original
        exception."""
        if self._publisher is not None:
            self._publisher.drain(reraise=reraise)

    def _publish_once(self, final: str, arrays: Dict[str, np.ndarray],
                      payload: dict) -> str:
        iteration = int(payload.get("iteration", 0))
        manifest_extra = {"iteration": iteration}
        if "layout" in payload:
            # The logical-layout digest rides the manifest: a resuming run
            # can check layout compatibility before touching arrays.npz.
            manifest_extra["layout_digest"] = layout_digest(payload["layout"])
        with atomic_dir(final) as tmp:
            with open(os.path.join(tmp, "arrays.npz"), "wb") as f:
                np.savez(f, **arrays)
            with open(os.path.join(tmp, "state.json"), "w") as f:
                json.dump(payload, f, indent=1)
            # The torn-write window fault injection aims at: payload files
            # exist, manifest/publish has not happened.  A kill here leaves
            # only an invisible .tmp dir — LATEST still names the previous
            # complete checkpoint.  The site fires on the publisher thread
            # in async mode, so the atomicity tests exercise the real
            # concurrent window.
            fault_point("checkpoint:write", iteration=iteration)
            write_manifest(tmp, extra=manifest_extra)
        atomic_write_bytes(
            os.path.join(self.directory, LATEST_NAME),
            os.path.basename(final).encode(),
        )
        self._prune(keep_name=os.path.basename(final))
        return final

    def _prune(self, keep_name: str) -> None:
        """Drop all but the newest ``keep`` published checkpoints (the one
        just written always survives), plus any ``.tmp-*``/``.old-*``
        debris a hard kill left behind — saves are sequential within the
        writing rank, so anything with those prefixes is stale by the time
        a later save prunes."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        names = sorted(
            n for n in entries
            if n.startswith("ckpt-")
            and os.path.isdir(os.path.join(self.directory, n))
        )
        stale = [n for n in entries if n.startswith((".tmp-", ".old-"))]
        for name in stale + names[:-self.keep]:
            if name != keep_name:
                shutil.rmtree(
                    os.path.join(self.directory, name), ignore_errors=True
                )

    # -- load ----------------------------------------------------------------
    @staticmethod
    def read_payload(path: str) -> tuple:
        """(payload, arrays) of one checkpoint-version directory, manifest
        verified first and the read retried like any guarded IO."""
        if not os.path.isdir(path):
            raise CheckpointError(f"no checkpoint directory at {path!r}")
        verify_manifest(path)

        def _read():
            fault_point("checkpoint:read", path=path)
            with open(os.path.join(path, "state.json")) as f:
                payload = json.load(f)
            with np.load(os.path.join(path, "arrays.npz")) as arrays:
                return payload, {k: arrays[k] for k in arrays.files}

        payload, arrays = retry_call(_read, site="checkpoint:io")
        if payload.get("version") != STATE_VERSION:
            raise CheckpointError(
                f"{path}: checkpoint version {payload.get('version')!r} "
                f"!= supported {STATE_VERSION}"
            )
        return payload, arrays

    def resolve_resume(self, resume: str) -> Optional[str]:
        """Resolve a ``resume`` spec to a checkpoint-version path: ``auto``
        returns None when nothing is checkpointed yet, ``latest`` requires a
        published checkpoint, anything else is an explicit path."""
        if resume in ("auto", "latest"):
            path = self.latest_path()
            if path is None and resume == "latest":
                raise CheckpointError(
                    f"--resume latest: no checkpoint under {self.directory}"
                )
            return path
        return resume


def _state_layout(state: "DescentState") -> dict:
    """The snapshot's logical (mesh-independent) layout, recorded in the
    payload and digested into the manifest: unpadded score-row lengths plus
    each coordinate model's entity-vocabulary size and dimension.  Padded
    and sharded shapes are deliberately ABSENT — they belong to the mesh
    that happens to execute the fit, and the resuming run derives its own
    (reshard_to_mesh / the engines' load_rows)."""
    from photon_tpu.game.model import RandomEffectModel

    coords = {}
    for name, model in state.models.items():
        if isinstance(model, RandomEffectModel):
            coords[name] = {
                "kind": "random",
                "entities": int(model.num_entities),
                "dim": int(model.dim),
            }
        else:
            coords[name] = {
                "kind": "fixed",
                "dim": int(model.coefficients.means.shape[0]),
            }
    return {
        "rows": {
            name: int(row.shape[0])
            for name, row in state.residual_rows.items()
        },
        "coordinates": coords,
    }


class DescentCheckpointer(CheckpointPublisherBase):
    """Versioned GAME-descent checkpoints (see module docstring): the
    descent's full restart state serialized through the shared publisher."""

    # -- save ----------------------------------------------------------------
    def save(self, state: DescentState) -> Optional[str]:
        """Stage + publish ``state``; returns the checkpoint path (None on
        non-writing ranks).  See :meth:`CheckpointPublisherBase.save_arrays`
        for the sync/async semantics."""
        if not self._should_write():
            return None
        arrays, models_meta = _models_to_arrays("m", state.models)
        # When the best model IS the current iterate (the common improving-
        # run case), its coordinate models are the same objects as
        # state.models' — store name references instead of fetching and
        # hashing every table twice.
        best_shared = sorted(
            name for name, model in state.best_models.items()
            if state.models.get(name) is model
        )
        best_arrays, best_meta = _models_to_arrays(
            "b",
            {
                name: model for name, model in state.best_models.items()
                if name not in set(best_shared)
            },
        )
        arrays.update(best_arrays)
        for j, (name, row) in enumerate(state.residual_rows.items()):
            arrays[f"r{j}__row"] = row
        payload = {
            "version": STATE_VERSION,
            "iteration": state.iteration,
            "num_iterations": state.num_iterations,
            "task_type": state.task_type,
            "models": models_meta,
            "best_models": best_meta,
            "best_shared": best_shared,
            "best_metrics": state.best_metrics,
            "best_iteration": state.best_iteration,
            "history": state.history,
            "residual_rows": list(state.residual_rows),
            "quarantined": state.quarantined,
            "fingerprint": state.fingerprint,
            "layout": _state_layout(state),
            "stream": state.stream,
        }
        # Streamed descents checkpoint MID-EPOCH (after every coordinate):
        # the version name follows the monotonic stream sequence so two
        # snapshots of one iteration never collide; resident descents keep
        # the one-version-per-iteration naming.
        seq = state.iteration
        if state.stream:
            seq = int(state.stream.get("seq", state.iteration))
        return self.save_arrays(seq, arrays, payload)

    # -- load ----------------------------------------------------------------
    def load(self, resume: str, mesh=None) -> Optional[DescentState]:
        """Resolve ``resume`` and load: ``auto`` returns None when nothing
        is checkpointed yet, ``latest`` requires a checkpoint, anything else
        is an explicit checkpoint-version directory path.  ``mesh`` is the
        RESUMING run's mesh (any shape — checkpoints are mesh-portable):
        restored model state is placed for it."""
        path = self.resolve_resume(resume)
        if path is None:
            return None
        return self.load_path(path, mesh=mesh)

    @staticmethod
    def load_path(path: str, mesh=None) -> DescentState:
        """Load one checkpoint-version directory, verifying its manifest.
        Model tables come back at their logical shapes, placed for ``mesh``
        (the resuming run's — NOT necessarily the writing run's)."""
        payload, arrays = CheckpointPublisherBase.read_payload(path)
        layout = payload.get("layout")
        if layout is not None:
            # Cross-check the manifest's advertised layout digest against
            # the payload it actually shipped: the manifest hash catches
            # corruption, this catches a writer bug / mixed-version
            # artifact where the two were written inconsistently.  The
            # re-read is guarded IO like every other checkpoint read.
            def _read_manifest():
                with open(os.path.join(path, "manifest.json")) as f:
                    return json.load(f)

            advertised = retry_call(
                _read_manifest, site="checkpoint:io"
            ).get("extra", {}).get("layout_digest")
            if advertised is not None and advertised != layout_digest(layout):
                raise CheckpointError(
                    f"{path}: manifest layout digest {advertised!r} does "
                    "not match the payload layout — inconsistent checkpoint "
                    "artifact; refusing to resume"
                )
        task = payload["task_type"]
        models = _models_from_arrays(
            "m", payload["models"], arrays, task, mesh=mesh
        )
        best_models = _models_from_arrays(
            "b", payload["best_models"], arrays, task, mesh=mesh
        )
        for name in payload.get("best_shared", []):
            best_models[name] = models[name]
        # Keep the composite's coordinate order (the update sequence) stable
        # across the reference-dedup round trip.
        best_models = {
            name: best_models[name] for name in models if name in best_models
        } | {
            name: model for name, model in best_models.items()
            if name not in models
        }
        return DescentState(
            iteration=payload["iteration"],
            num_iterations=payload["num_iterations"],
            task_type=task,
            models=models,
            best_models=best_models,
            best_metrics=dict(payload["best_metrics"]),
            best_iteration=payload["best_iteration"],
            history=list(payload["history"]),
            residual_rows={
                name: arrays[f"r{j}__row"]
                for j, name in enumerate(payload["residual_rows"])
            },
            quarantined=int(payload.get("quarantined", 0)),
            fingerprint=payload.get("fingerprint", {}),
            stream=payload.get("stream"),
        )


# -- streamed-GLM L-BFGS checkpoints ----------------------------------------


@dataclasses.dataclass
class StreamState:
    """Mid-fit (or completed) streamed L-BFGS state: everything
    :func:`photon_tpu.data.streaming.streaming_lbfgs` needs to continue a
    fit exactly where it left off — iterate, gradient, curvature-pair ring
    buffer, convergence history, and the host-loop scalars.  ``completed``
    marks a final snapshot (the fit converged; resume rebuilds the result
    without streaming a single pass)."""

    iteration: int
    arrays: Dict[str, np.ndarray]   # w, g, S, Y, rho, hv, hg, hvalid
    scalars: dict                   # f, gnorm0, num_pairs, insert_pos, gamma
    completed: bool
    reason: int
    fingerprint: dict


class StreamCheckpointer(CheckpointPublisherBase):
    """Streamed-GLM L-BFGS checkpoints through the same atomic protocol
    and async publisher as the descent checkpoints (the ROADMAP's
    streamed-GLM mid-fit edge).  One instance owns one lambda's chain."""

    KIND = "stream-lbfgs"

    def save(self, state: StreamState) -> Optional[str]:
        if not self._should_write():
            return None
        payload = {
            "version": STATE_VERSION,
            "kind": self.KIND,
            "iteration": state.iteration,
            "scalars": state.scalars,
            "completed": state.completed,
            "reason": state.reason,
            "arrays": sorted(state.arrays),
            "fingerprint": state.fingerprint,
        }
        return self.save_arrays(state.iteration, dict(state.arrays), payload)

    def load(self, resume: str) -> Optional[StreamState]:
        path = self.resolve_resume(resume)
        if path is None:
            return None
        payload, arrays = self.read_payload(path)
        if payload.get("kind") != self.KIND:
            raise CheckpointError(
                f"{path}: not a streamed-GLM checkpoint "
                f"(kind={payload.get('kind')!r})"
            )
        return StreamState(
            iteration=int(payload["iteration"]),
            arrays={k: arrays[k] for k in payload["arrays"]},
            scalars=dict(payload["scalars"]),
            completed=bool(payload.get("completed", False)),
            reason=int(payload.get("reason", 0)),
            fingerprint=payload.get("fingerprint", {}),
        )
