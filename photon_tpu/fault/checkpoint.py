"""Preemption-safe checkpoint/resume for GAME coordinate descent.

The reference's recovery story is Spark lineage plus per-iteration HDFS
model dumps; a preempted TPU slice has neither.  This module snapshots the
full descent state after every outer iteration — per-coordinate models,
the residual engine's score rows (fetched once, off the hot path), the
best-model-so-far, validation-metric history, and the iteration/quarantine
counters — into a versioned on-disk checkpoint published with the atomic
protocol of :mod:`photon_tpu.fault.atomic`:

    <dir>/ckpt-000002/
        state.json      # iteration, history, best metrics, fingerprint
        arrays.npz      # model tables + residual score rows (exact dtypes)
        manifest.json   # content hashes, written last
    <dir>/LATEST        # pointer file, replaced atomically

Resume rebuilds the device score tables from the snapshot rows and warm
starts every coordinate from its checkpointed model, so a resumed fit is
numerically identical to an uninterrupted one (score rows round-trip at
their native dtype: f32 for the device engine, f64 for the host escape
hatch).  Under multi-controller runs every rank LOADS the checkpoint (the
directory must be on storage all ranks can read) but only rank 0 WRITES —
the same primary-writes rule the drivers use for models and reports.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import time
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from photon_tpu.fault.atomic import (
    atomic_dir,
    atomic_write_bytes,
    verify_manifest,
    write_manifest,
)
from photon_tpu.fault.injection import fault_point
from photon_tpu.fault.retry import retry_call
from photon_tpu.telemetry import NULL_SESSION

STATE_VERSION = 1
LATEST_NAME = "LATEST"


class CheckpointError(RuntimeError):
    """A checkpoint could not be loaded (missing, corrupt, or mismatched)."""


def descent_fingerprint(
    task_type: str, coordinate_names, num_examples: int, residual_mode: str,
    config_key: Optional[str] = None,
    validation_key: Optional[str] = None,
    locked=(),
    warm_start: bool = False,
) -> dict:
    """The ONE definition of a descent run's checkpoint-compatibility
    fingerprint (descent and estimator both check against it): a resumed
    run must be the same descent — same task, coordinate update sequence,
    data size, residual mode, optimization configuration (when the caller
    supplies a key), validation setup (primary evaluator, or None for an
    unevaluated fit), lock list, and warm-start-ness — or the restored
    state would silently be another run's model (or crash on a
    best-metrics shape it never tracked)."""
    fp = {
        "task_type": task_type,
        "coordinates": list(coordinate_names),
        "num_examples": int(num_examples),
        "residual_mode": residual_mode,
        "validation": validation_key,
        "locked": sorted(locked),
        "warm_start": bool(warm_start),
    }
    if config_key is not None:
        fp["config"] = config_key
    return fp


def configuration_key(coordinate_configs: dict) -> str:
    """Digest of a sweep point's per-coordinate optimization configs
    (regularization weights, solver settings — frozen-dataclass reprs are
    deterministic and content-bearing).  Deliberately EXCLUDES
    ``descent_iterations``: resuming with more iterations is a supported
    continuation, a different regularization is a different model."""
    import hashlib

    return hashlib.sha256(repr(coordinate_configs).encode()).hexdigest()[:16]


@dataclasses.dataclass
class DescentState:
    """One outer iteration's complete restart state (live model objects;
    (de)serialization to arrays happens in the checkpointer)."""

    iteration: int              # last COMPLETED outer iteration
    num_iterations: int         # the run's target iteration count
    task_type: str
    models: Dict[str, object]
    best_models: Dict[str, object]
    best_metrics: Dict[str, float]
    best_iteration: int
    history: List[dict]
    residual_rows: Dict[str, np.ndarray]
    quarantined: int
    fingerprint: dict

    @property
    def completed(self) -> bool:
        return self.iteration + 1 >= self.num_iterations


# -- model <-> array serialization ------------------------------------------


def _models_to_arrays(prefix: str, models: Dict[str, object]):
    """(arrays, meta) for one model dict; array keys are
    ``<prefix><i>__<field>`` (npz-safe, order = meta order)."""
    from photon_tpu.game.model import FixedEffectModel, RandomEffectModel
    from photon_tpu.parallel.mesh import to_host

    arrays, meta = {}, []
    for i, (name, model) in enumerate(models.items()):
        key = f"{prefix}{i}__"
        if isinstance(model, FixedEffectModel):
            coeff = model.coefficients
            arrays[key + "means"] = to_host(coeff.means)
            if coeff.variances is not None:
                arrays[key + "variances"] = to_host(coeff.variances)
            meta.append({
                "name": name, "kind": "fixed", "shard_name": model.shard_name,
                "has_variances": coeff.variances is not None,
            })
        elif isinstance(model, RandomEffectModel):
            arrays[key + "table"] = to_host(model.table)
            arrays[key + "keys"] = np.asarray(model.keys)
            if model.variances is not None:
                arrays[key + "variances"] = to_host(model.variances)
            meta.append({
                "name": name, "kind": "random", "shard_name": model.shard_name,
                "entity_column": model.entity_column,
                "has_variances": model.variances is not None,
            })
        else:
            raise TypeError(f"cannot checkpoint coordinate model {type(model)!r}")
    return arrays, meta


def _models_from_arrays(prefix: str, meta: List[dict], arrays, task_type: str):
    from photon_tpu.game.model import FixedEffectModel, RandomEffectModel
    from photon_tpu.models.glm import Coefficients, model_for_task

    models = {}
    for i, m in enumerate(meta):
        key = f"{prefix}{i}__"
        variances = (
            jnp.asarray(arrays[key + "variances"]) if m["has_variances"] else None
        )
        if m["kind"] == "fixed":
            glm = model_for_task(
                task_type,
                Coefficients(jnp.asarray(arrays[key + "means"]), variances),
            )
            models[m["name"]] = FixedEffectModel(
                model=glm, shard_name=m["shard_name"]
            )
        else:
            models[m["name"]] = RandomEffectModel(
                table=jnp.asarray(arrays[key + "table"]),
                keys=np.asarray(arrays[key + "keys"]),
                entity_column=m["entity_column"],
                shard_name=m["shard_name"],
                task_type=task_type,
                variances=variances,
            )
    return models


class DescentCheckpointer:
    """Writes/reads versioned descent checkpoints under one directory.

    ``write`` defaults to ``jax.process_index() == 0`` at save time
    (rank-0-writes); every rank may load.  ``keep`` bounds on-disk versions
    (older checkpoints are pruned after a successful publish).
    """

    def __init__(self, directory: str, telemetry=None, logger=None,
                 keep: int = 2, write: Optional[bool] = None):
        self.directory = directory
        self.telemetry = telemetry or NULL_SESSION
        self.logger = logger
        self.keep = max(1, keep)
        self._write = write

    # -- helpers -------------------------------------------------------------
    def _should_write(self) -> bool:
        if self._write is not None:
            return self._write
        import jax

        return jax.process_index() == 0

    def _ckpt_name(self, iteration: int) -> str:
        return f"ckpt-{iteration:06d}"

    def latest_path(self) -> Optional[str]:
        """The checkpoint directory LATEST points to, or None."""
        pointer = os.path.join(self.directory, LATEST_NAME)
        if not os.path.isfile(pointer):
            return None
        with open(pointer) as f:
            name = f.read().strip()
        path = os.path.join(self.directory, name)
        return path if os.path.isdir(path) else None

    # -- save ----------------------------------------------------------------
    def save(self, state: DescentState) -> Optional[str]:
        """Publish ``state`` atomically; returns the checkpoint path (None
        on non-writing ranks).  Checkpoint IO retries like any other
        guarded write; an exhausted retry raises — a run that cannot
        checkpoint is a failed run, not a silently unprotected one."""
        if not self._should_write():
            return None
        t0 = time.monotonic()
        path = retry_call(
            lambda: self._save_once(state), site="checkpoint:io",
            telemetry=self.telemetry, logger=self.logger,
        )
        self.telemetry.histogram("checkpoint.write_seconds").observe(
            time.monotonic() - t0
        )
        self.telemetry.counter("checkpoint.saves").inc()
        if self.logger is not None:
            self.logger.info(
                "checkpoint: iteration %d -> %s", state.iteration, path
            )
        return path

    def _save_once(self, state: DescentState) -> str:
        final = os.path.join(self.directory, self._ckpt_name(state.iteration))
        arrays, models_meta = _models_to_arrays("m", state.models)
        # When the best model IS the current iterate (the common improving-
        # run case), its coordinate models are the same objects as
        # state.models' — store name references instead of fetching and
        # hashing every table twice.
        best_shared = sorted(
            name for name, model in state.best_models.items()
            if state.models.get(name) is model
        )
        best_arrays, best_meta = _models_to_arrays(
            "b",
            {
                name: model for name, model in state.best_models.items()
                if name not in set(best_shared)
            },
        )
        arrays.update(best_arrays)
        for j, (name, row) in enumerate(state.residual_rows.items()):
            arrays[f"r{j}__row"] = np.asarray(row)
        payload = {
            "version": STATE_VERSION,
            "iteration": state.iteration,
            "num_iterations": state.num_iterations,
            "task_type": state.task_type,
            "models": models_meta,
            "best_models": best_meta,
            "best_shared": best_shared,
            "best_metrics": state.best_metrics,
            "best_iteration": state.best_iteration,
            "history": state.history,
            "residual_rows": list(state.residual_rows),
            "quarantined": state.quarantined,
            "fingerprint": state.fingerprint,
        }
        with atomic_dir(final) as tmp:
            with open(os.path.join(tmp, "arrays.npz"), "wb") as f:
                np.savez(f, **arrays)
            with open(os.path.join(tmp, "state.json"), "w") as f:
                json.dump(payload, f, indent=1)
            # The torn-write window fault injection aims at: payload files
            # exist, manifest/publish has not happened.  A kill here leaves
            # only an invisible .tmp dir — LATEST still names the previous
            # complete checkpoint.
            fault_point("checkpoint:write", iteration=state.iteration)
            write_manifest(tmp, extra={"iteration": state.iteration})
        atomic_write_bytes(
            os.path.join(self.directory, LATEST_NAME),
            os.path.basename(final).encode(),
        )
        self._prune(keep_name=os.path.basename(final))
        return final

    def _prune(self, keep_name: str) -> None:
        """Drop all but the newest ``keep`` published checkpoints (the one
        just written always survives), plus any ``.tmp-*``/``.old-*``
        debris a hard kill left behind — saves are sequential within the
        writing rank, so anything with those prefixes is stale by the time
        a later save prunes."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        names = sorted(
            n for n in entries
            if n.startswith("ckpt-")
            and os.path.isdir(os.path.join(self.directory, n))
        )
        stale = [n for n in entries if n.startswith((".tmp-", ".old-"))]
        for name in stale + names[:-self.keep]:
            if name != keep_name:
                shutil.rmtree(
                    os.path.join(self.directory, name), ignore_errors=True
                )

    # -- load ----------------------------------------------------------------
    def load(self, resume: str) -> Optional[DescentState]:
        """Resolve ``resume`` and load: ``auto`` returns None when nothing
        is checkpointed yet, ``latest`` requires a checkpoint, anything else
        is an explicit checkpoint-version directory path."""
        if resume in ("auto", "latest"):
            path = self.latest_path()
            if path is None:
                if resume == "latest":
                    raise CheckpointError(
                        f"--resume latest: no checkpoint under {self.directory}"
                    )
                return None
            return self.load_path(path)
        return self.load_path(resume)

    @staticmethod
    def load_path(path: str) -> DescentState:
        """Load one checkpoint-version directory, verifying its manifest."""
        if not os.path.isdir(path):
            raise CheckpointError(f"no checkpoint directory at {path!r}")
        verify_manifest(path)

        def _read():
            fault_point("checkpoint:read", path=path)
            with open(os.path.join(path, "state.json")) as f:
                payload = json.load(f)
            with np.load(os.path.join(path, "arrays.npz")) as arrays:
                return payload, {k: arrays[k] for k in arrays.files}

        payload, arrays = retry_call(_read, site="checkpoint:io")
        if payload.get("version") != STATE_VERSION:
            raise CheckpointError(
                f"{path}: checkpoint version {payload.get('version')!r} "
                f"!= supported {STATE_VERSION}"
            )
        task = payload["task_type"]
        models = _models_from_arrays("m", payload["models"], arrays, task)
        best_models = _models_from_arrays(
            "b", payload["best_models"], arrays, task
        )
        for name in payload.get("best_shared", []):
            best_models[name] = models[name]
        # Keep the composite's coordinate order (the update sequence) stable
        # across the reference-dedup round trip.
        best_models = {
            name: best_models[name] for name in models if name in best_models
        } | {
            name: model for name, model in best_models.items()
            if name not in models
        }
        return DescentState(
            iteration=payload["iteration"],
            num_iterations=payload["num_iterations"],
            task_type=task,
            models=models,
            best_models=best_models,
            best_metrics=dict(payload["best_metrics"]),
            best_iteration=payload["best_iteration"],
            history=list(payload["history"]),
            residual_rows={
                name: arrays[f"r{j}__row"]
                for j, name in enumerate(payload["residual_rows"])
            },
            quarantined=int(payload.get("quarantined", 0)),
            fingerprint=payload.get("fingerprint", {}),
        )
