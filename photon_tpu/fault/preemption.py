"""Preemption-aware shutdown: turn SIGTERM into one last checkpoint.

Preemptible/spot capacity does not crash — it WARNS: the scheduler sends
SIGTERM and gives the process a grace window before SIGKILL.  The reference
rides Spark's driver re-submission and loses the in-flight work; here the
warning is converted into a clean iteration-boundary exit:

1. A signal handler (installed by the drivers under ``--on-preempt
   checkpoint``, the default) sets a process-wide flag — signal-safe: the
   handler does nothing but record the request.
2. The training loops (GAME coordinate descent and the streamed-GLM
   L-BFGS host loop) poll :func:`preemption_requested` at their iteration
   boundaries — the exact points where the checkpoint state is consistent —
   force a final synchronous save through the existing ``AsyncPublisher``
   drain, and raise :class:`PreemptedError`.
3. The driver maps :class:`PreemptedError` to the distinct exit code
   :data:`PREEMPTED_EXIT_CODE` (75, ``EX_TEMPFAIL``: "try again later" —
   schedulers and wrappers can tell a preemption from a crash), after the
   telemetry run report is finalized with status ``preempted``.

``--on-preempt ignore`` leaves the default signal behavior untouched
(SIGTERM kills mid-iteration; the atomic checkpoint protocol still
guarantees the previous published checkpoint survives — preemption
handling narrows the loss window from one iteration to zero).

CI-testability: the ``preempt`` fault site (``--faults preempt:iter=k``)
sets the same flag at the top of loop iteration ``k`` — no signals
involved, so the full preempt → final-save → exit-code → resume-parity
path runs as an ordinary deterministic test.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional

# EX_TEMPFAIL: the conventional "transient, retry me" exit status — distinct
# from 1 (crash) so run wrappers can resubmit preempted runs automatically.
PREEMPTED_EXIT_CODE = 75

_requested = threading.Event()
_reason: Optional[str] = None


class PreemptedError(RuntimeError):
    """The run stopped at an iteration boundary because preemption was
    requested (SIGTERM/SIGINT under ``--on-preempt checkpoint``, or the
    injected ``preempt`` fault site).  The last completed iteration's
    checkpoint is published by the time this propagates; the driver exits
    with :data:`PREEMPTED_EXIT_CODE`."""


def request_preemption(reason: str = "signal") -> None:
    """Record a preemption request (signal-safe: sets a flag, nothing
    else).  The training loops act on it at their next iteration
    boundary."""
    global _reason
    _reason = reason
    _requested.set()


def preemption_requested() -> bool:
    return _requested.is_set()


def preemption_reason() -> Optional[str]:
    return _reason


def clear_preemption() -> None:
    """Reset the flag (run scoped: drivers clear on entry so one run's
    late signal cannot preempt the next run in the same process)."""
    global _reason
    _reason = None
    _requested.clear()


def consume_preempt_injection(iteration: int) -> None:
    """The CI face of preemption: when the active fault plan has a
    ``preempt`` rule matching this iteration (``--faults preempt:iter=k``),
    set the preemption flag exactly as the signal handler would."""
    from photon_tpu.fault.injection import active_plan

    plan = active_plan()
    if plan is not None and plan.consume(
        "preempt", iteration=iteration
    ) is not None:
        request_preemption(f"injected at iteration {iteration}")


class PreemptionHandler:
    """Context manager installing SIGTERM/SIGINT handlers that set the
    preemption flag; previous handlers are restored on exit.

    Installation is a no-op off the main thread (Python only allows signal
    handlers there — e.g. drivers invoked from a test worker thread) and
    under ``mode='ignore'``.  The flag is cleared on entry either way, so
    every run starts un-preempted.

    Only drivers whose loops actually POLL the flag install this (the
    ``preemptible`` gate in ``drivers.common.telemetry_run``): a handler
    that swallows SIGINT in a driver nothing ever polls would make that
    driver uninterruptible.  A SECOND signal is the operator insisting:
    the previous handlers are restored and the signal re-raised, so a
    double Ctrl-C always behaves like stock Python even mid-phase (data
    load, compile) before the first boundary check runs.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, mode: str = "checkpoint", logger=None):
        if mode not in ("checkpoint", "ignore"):
            raise ValueError(
                f"--on-preempt must be 'checkpoint' or 'ignore', got {mode!r}"
            )
        self.mode = mode
        self.logger = logger
        self._previous: dict = {}

    def _handle(self, signum, frame):
        del frame
        if preemption_requested():
            # Second signal: stop being polite — restore the previous
            # handlers and deliver this signal through them (default
            # SIGTERM terminates, default SIGINT raises
            # KeyboardInterrupt).
            for sig, prev in self._previous.items():
                signal.signal(sig, prev)
            self._previous.clear()
            signal.raise_signal(signum)
            return
        request_preemption(signal.Signals(signum).name)
        if self.logger is not None:
            self.logger.info(
                "%s received: will checkpoint and exit at the next "
                "iteration boundary (signal again to stop immediately)",
                signal.Signals(signum).name,
            )

    def __enter__(self) -> "PreemptionHandler":
        clear_preemption()
        if (self.mode == "checkpoint"
                and threading.current_thread() is threading.main_thread()):
            for sig in self.SIGNALS:
                self._previous[sig] = signal.signal(sig, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
        clear_preemption()
