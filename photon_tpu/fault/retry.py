"""Retry with jittered, capped exponential backoff for transient IO.

The reference rides Spark task re-execution for transient storage hiccups;
here every guarded read/write path (Avro and LIBSVM file reads, checkpoint
IO) routes through :func:`retry_call`, which retries ``OSError``-class
failures with exponential backoff — jittered so a fleet of workers hitting
the same flaky store does not retry in lockstep, capped so backoff never
stalls a run, and telemetry-counted (``io.retries{site=...}``) so recovered
faults stay visible in the run report instead of vanishing into a log line.

Hangs, not just failures: with a stall timeout configured (``--stall-
timeout`` / ``PHOTON_STALL_TIMEOUT_S``), each attempt runs under
:func:`photon_tpu.fault.watchdog.call_with_timeout` — a call that makes no
progress for the timeout raises
:class:`~photon_tpu.fault.watchdog.IOStallTimeoutError` (an ``OSError``),
which this module then retries like any transient failure
(``io.stall_timeouts{site=...}`` counts the escalations).  Every attempt
also heartbeats its site, so the run watchdog can tell a slow-but-alive IO
path from a wedged one.

Knobs: ``PHOTON_IO_RETRIES`` (retries after the first attempt, default 4),
``PHOTON_IO_RETRY_BASE_S`` (first backoff, default 0.05s; tests set 0),
``PHOTON_STALL_TIMEOUT_S`` (per-attempt stall timeout, default 0 = off).
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from collections import Counter
from typing import Callable, Optional, Tuple, Type, TypeVar

from photon_tpu.telemetry import NULL_SESSION

T = TypeVar("T")

# Process-wide recovered-retry totals by site: introspection for paths that
# run without a telemetry session (streamed readers, library use).
RETRY_TOTALS: Counter = Counter()


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """``attempts`` is the TOTAL number of tries (1 disables retrying).

    ``stall_timeout_s`` > 0 bounds each attempt's wall clock: a hung call
    is escalated to a retriable :class:`~photon_tpu.fault.watchdog.
    IOStallTimeoutError` instead of blocking the run forever."""

    attempts: int = 5
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.25
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)
    stall_timeout_s: float = 0.0

    def delay(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        """Backoff before retry ``attempt`` (0-based): exponential, capped,
        with up to ``jitter`` fractional noise on top.  ``rng`` defaults to
        the module RNG so out-of-loop callers (the fleet supervisor's
        respawn backoff) can reuse the one backoff shape."""
        # min(attempt, 62): 2.0**attempt overflows float range past ~1024
        # attempts (long-lived callers like the supervisor's respawn loop);
        # the cap is far past where max_delay_s saturates anyway.
        base = min(self.max_delay_s,
                   self.base_delay_s * (2.0 ** min(attempt, 62)))
        roll = rng.random() if rng is not None else random.random()
        return base * (1.0 + self.jitter * roll)


def default_policy() -> RetryPolicy:
    from photon_tpu.fault.watchdog import stall_timeout
    from photon_tpu.utils.env import env_int

    retries = env_int("PHOTON_IO_RETRIES", 4, minimum=0)
    raw = os.environ.get("PHOTON_IO_RETRY_BASE_S")
    try:
        base = 0.05 if raw is None else max(0.0, float(raw))
    except ValueError:
        base = 0.05
    return RetryPolicy(
        attempts=retries + 1, base_delay_s=base,
        stall_timeout_s=stall_timeout(),
    )


def retry_call(
    fn: Callable[[], T],
    *,
    site: str,
    telemetry=None,
    policy: Optional[RetryPolicy] = None,
    logger=None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """``fn()`` with up to ``policy.attempts`` tries.

    Each RECOVERED failure (one that a later attempt follows) increments the
    ``io.retries{site=}`` counter and the module :data:`RETRY_TOTALS`; the
    final failure re-raises untouched, so callers see the real error with
    its real traceback.  InjectedIOError from the fault plan is an OSError
    and retries like any other transient fault — that is the point.
    """
    import threading

    from photon_tpu.fault.watchdog import (
        IOStallTimeoutError,
        call_with_timeout,
        complete,
        heartbeat,
    )

    policy = policy or default_policy()
    t = telemetry or NULL_SESSION
    rng = random.Random()
    attempt = 0
    # Per-CALL heartbeat identity (site + calling thread): concurrent
    # IO-pool workers share a site name, and a per-site key would let one
    # worker's completion retire the mark while another worker of the same
    # site is still wedged — hiding that hang from the watchdog.
    site_key = f"io.{site}@t{threading.get_ident()}"
    try:
        while True:
            try:
                # Every attempt is watchdog-visible progress (retired once
                # the call sequence ends — on ANY exit, including
                # non-retriable errors; silence from finished IO is not a
                # stall); with a stall timeout the attempt runs on a
                # guarded worker thread and a hang escalates to a
                # retriable timeout (the retry/timeout/backoff triangle).
                heartbeat(site_key)
                if policy.stall_timeout_s > 0:
                    # The per-attempt budget DOUBLES each retry: a wedged
                    # call is abandoned fast, while IO legitimately slower
                    # than the configured timeout earns enough budget to
                    # finish before the attempts run out (1x, 2x, 4x, ...).
                    return call_with_timeout(
                        fn, policy.stall_timeout_s * (2.0 ** attempt),
                        site=site,
                    )
                return fn()
            except policy.retry_on as e:
                if isinstance(e, IOStallTimeoutError):
                    t.counter("io.stall_timeouts", site=site).inc()
                if attempt >= policy.attempts - 1:
                    raise
                t.counter("io.retries", site=site).inc()
                RETRY_TOTALS[site] += 1
                delay = policy.delay(attempt, rng)
                if logger is not None:
                    logger.info(
                        "retrying %s after %s: %s (attempt %d/%d, "
                        "backoff %.3fs)",
                        site, type(e).__name__, e, attempt + 2,
                        policy.attempts, delay,
                    )
                if delay > 0:
                    sleep(delay)
                attempt += 1
    finally:
        complete(site_key)
