"""Retry with jittered, capped exponential backoff for transient IO.

The reference rides Spark task re-execution for transient storage hiccups;
here every guarded read/write path (Avro and LIBSVM file reads, checkpoint
IO) routes through :func:`retry_call`, which retries ``OSError``-class
failures with exponential backoff — jittered so a fleet of workers hitting
the same flaky store does not retry in lockstep, capped so backoff never
stalls a run, and telemetry-counted (``io.retries{site=...}``) so recovered
faults stay visible in the run report instead of vanishing into a log line.

Knobs: ``PHOTON_IO_RETRIES`` (retries after the first attempt, default 4),
``PHOTON_IO_RETRY_BASE_S`` (first backoff, default 0.05s; tests set 0).
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from collections import Counter
from typing import Callable, Optional, Tuple, Type, TypeVar

from photon_tpu.telemetry import NULL_SESSION

T = TypeVar("T")

# Process-wide recovered-retry totals by site: introspection for paths that
# run without a telemetry session (streamed readers, library use).
RETRY_TOTALS: Counter = Counter()


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """``attempts`` is the TOTAL number of tries (1 disables retrying)."""

    attempts: int = 5
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.25
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry ``attempt`` (0-based): exponential, capped,
        with up to ``jitter`` fractional noise on top."""
        base = min(self.max_delay_s, self.base_delay_s * (2.0 ** attempt))
        return base * (1.0 + self.jitter * rng.random())


def default_policy() -> RetryPolicy:
    from photon_tpu.utils.env import env_int

    retries = env_int("PHOTON_IO_RETRIES", 4, minimum=0)
    raw = os.environ.get("PHOTON_IO_RETRY_BASE_S")
    try:
        base = 0.05 if raw is None else max(0.0, float(raw))
    except ValueError:
        base = 0.05
    return RetryPolicy(attempts=retries + 1, base_delay_s=base)


def retry_call(
    fn: Callable[[], T],
    *,
    site: str,
    telemetry=None,
    policy: Optional[RetryPolicy] = None,
    logger=None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """``fn()`` with up to ``policy.attempts`` tries.

    Each RECOVERED failure (one that a later attempt follows) increments the
    ``io.retries{site=}`` counter and the module :data:`RETRY_TOTALS`; the
    final failure re-raises untouched, so callers see the real error with
    its real traceback.  InjectedIOError from the fault plan is an OSError
    and retries like any other transient fault — that is the point.
    """
    policy = policy or default_policy()
    t = telemetry or NULL_SESSION
    rng = random.Random()
    attempt = 0
    while True:
        try:
            return fn()
        except policy.retry_on as e:
            if attempt >= policy.attempts - 1:
                raise
            t.counter("io.retries", site=site).inc()
            RETRY_TOTALS[site] += 1
            delay = policy.delay(attempt, rng)
            if logger is not None:
                logger.info(
                    "retrying %s after %s: %s (attempt %d/%d, backoff %.3fs)",
                    site, type(e).__name__, e, attempt + 2, policy.attempts,
                    delay,
                )
            if delay > 0:
                sleep(delay)
            attempt += 1
