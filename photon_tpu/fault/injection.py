"""Deterministic fault injection for recovery-path testing.

The reference inherits Spark's failure story: lineage re-computation plus
driver-log archaeology, exercised in production only when something actually
breaks.  This module makes failure a first-class, *testable* input instead:
a :class:`FaultPlan` — seedable, parsed from ``PHOTON_FAULTS`` or a driver's
``--faults`` flag — fires injected faults at named sites threaded through
the IO and training stack, so CI can prove the retry/checkpoint/quarantine
paths work rather than hoping they do (SURVEY.md §5 'Failure detection').

Spec grammar (comma-separated rules; tokens within a rule are colon-
separated; the first two tokens name the site, the rest are ``k=v`` params):

    PHOTON_FAULTS="io:read:p=0.3,descent:kill:iter=2,solve:nan:coord=per_item"

Sites and their actions:

- ``io:read`` / ``io:write`` — raise :class:`InjectedIOError` (an
  ``OSError``, so the retry layer treats it like any transient storage
  failure) at guarded read/write call sites.  Params: ``p`` (per-call fire
  probability, default 1.0), ``times`` (max fires, default unlimited).
- ``descent:kill`` — raise :class:`InjectedKillError` at the top of a GAME
  outer iteration, simulating a preempted process between iterations.
  Params: ``iter`` (fire when the iteration counter equals this), ``times``
  (default 1).  ``stream:kill`` is the streamed-GLM analog (top of an
  L-BFGS host-loop iteration).
- ``checkpoint:write`` — raise :class:`InjectedKillError` in the middle of
  a checkpoint write (after payload files, before the manifest/publish),
  the torn-write window the atomic protocol must survive.  Under the async
  publisher this site fires ON THE PUBLISHER THREAD and the failure
  surfaces at the training loop's next save (or final drain).  Params:
  ``times`` (default 1), ``p``.
- ``checkpoint:stage`` — raise :class:`InjectedKillError` at the start of a
  checkpoint's d2h staging step (before anything is written), the other
  async-publish kill window: the previously published checkpoint must stay
  the loadable LATEST.  Params: ``iter``, ``times`` (default 1), ``p``.
- ``solve:nan`` — corrupt a coordinate's solve output with NaNs (consumed
  via :func:`consume_nan_injection`, which returns True instead of
  raising).  Params: ``coord`` (coordinate name, or ``*`` for any),
  ``times`` (default 1).
- ``preempt`` — simulate a preemption WARNING (SIGTERM from a spot/
  preemptible scheduler): sets the process-wide preemption flag
  (:mod:`photon_tpu.fault.preemption`) at the top of a training-loop
  iteration instead of raising, so the loop checkpoints and exits with
  the preemption exit code exactly as under a real signal.  Params:
  ``iter`` (fire when the loop's iteration counter equals this),
  ``times`` (default 1).  A single-token site: the spec is
  ``preempt:iter=2`` — the parser treats a rule whose second token is a
  ``k=v`` pair as scope-only.

Determinism: every rule owns a ``random.Random`` seeded by
``(seed, site, rule index)`` — for a serial sequence of calls, the same
spec + seed fires at the same call positions on every run
(``PHOTON_FAULTS_SEED``, default 0).  When a fault site runs on concurrent
IO-pool workers (e.g. pooled native decodes), the SET of draws is still
seeded but their assignment to files follows thread scheduling — assert on
aggregate fire/retry counts there, not on which file faulted.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
from typing import Dict, Optional


class InjectedFaultError(Exception):
    """Marker base so tests/drivers can recognize injected faults."""


class InjectedIOError(OSError, InjectedFaultError):
    """An injected transient IO failure (retriable: it IS an OSError)."""


class InjectedKillError(RuntimeError, InjectedFaultError):
    """An injected process kill (not retriable; propagates out of the run
    like a preemption would, so the telemetry error-report and checkpoint
    recovery paths see exactly what a real kill leaves behind)."""


# The ONE registry of fault-site names consumed anywhere in the codebase,
# mapping site -> one-line behavior summary.  tests/test_fault_sites.py
# enforces the hygiene contract: every site consumed in code appears here,
# every registered site is documented in README's fault-site table, and
# every registered site is exercised by at least one test — a new site
# cannot land silently untested or undocumented.
KNOWN_FAULT_SITES = {
    "io:read": "transient IOError at guarded data/model reads (retriable)",
    "io:write": "transient IOError at guarded artifact writes (retriable)",
    "descent:kill": "process kill at the top of a GAME outer iteration",
    "stream:kill": "process kill at the top of a streamed L-BFGS iteration",
    "checkpoint:read": "transient IOError inside a checkpoint load "
                       "(retriable)",
    "checkpoint:write": "kill inside the checkpoint torn-write window "
                        "(payload written, manifest/publish not)",
    "checkpoint:stage": "kill at the start of checkpoint d2h staging",
    "solve:nan": "NaN-corrupt a named coordinate's solve output "
                 "(quarantine path)",
    "preempt": "set the preemption flag at a loop iteration boundary "
               "(checkpoint-and-exit path, exit code 75)",
    "tile:read": "transient IOError reading a tile-store part file "
                 "(disk tier of out-of-core GAME; retriable)",
    "tile:write": "transient IOError inside a tile-store publish "
                  "(before the atomic rename; retriable — the previous "
                  "part file stays intact)",
    "serve:replica_kill": "kill a serving replica's scoring path (param "
                          "replica=<id> targets one; the fleet router "
                          "marks it dead and reroutes in-flight work)",
    "transport:read": "transient IOError at a serving-transport frame "
                      "read (retriable: the client reconnects and "
                      "resends — scoring is idempotent)",
    "replica:crash": "hard-exit a serving replica's backing runtime "
                     "(a subprocess child os._exit()s; a thread replica "
                     "latches dead mid-batch) — the supervisor detects "
                     "the crash and resurrects",
    "replica:hang": "wedge a serving replica's scoring path without "
                    "failing it (consumed, not raised: the batch/child "
                    "sleeps) — detection must come from the supervisor's "
                    "probe deadline, exactly like a real hang",
    "replica:spawn": "transient failure spawning/respawning a serving "
                     "replica (retriable: the supervisor retries with "
                     "capped exponential backoff)",
    "online:ingest": "transient IOError reading an append-feed part file "
                     "(online-learning ingest; retriable — the part stays "
                     "pending and re-reads with backoff)",
    "online:refresh:kill": "kill an online refresh between train and "
                           "publish: the restarted service resumes the "
                           "COMPLETED fit from its round checkpoint and "
                           "publishes without retraining",
}


@dataclasses.dataclass
class FaultRule:
    """One parsed rule of a fault plan, with its firing state."""

    site: str
    params: Dict[str, str]
    rng: random.Random
    fires: int = 0

    @property
    def probability(self) -> float:
        return float(self.params.get("p", 1.0))

    @property
    def max_fires(self) -> Optional[int]:
        if "times" in self.params:
            return int(self.params["times"])
        # Probabilistic IO rules default to unlimited; deterministic rules
        # (kill / nan / explicit-iteration) fire once unless told otherwise.
        return None if "p" in self.params else 1

    def matches(self, site: str, ctx: Dict[str, object]) -> bool:
        if site != self.site:
            return False
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if "iter" in self.params:
            if ctx.get("iteration") != int(self.params["iter"]):
                return False
        if "coord" in self.params and self.params["coord"] != "*":
            if ctx.get("coordinate") != self.params["coord"]:
                return False
        if "replica" in self.params and self.params["replica"] != "*":
            if str(ctx.get("replica")) != self.params["replica"]:
                return False
        return True

    def roll(self) -> bool:
        """Consume one deterministic draw; True when the rule fires."""
        p = self.probability
        fired = p >= 1.0 or self.rng.random() < p
        if fired:
            self.fires += 1
        return fired


class FaultPlan:
    """A parsed set of fault rules with deterministic firing state.

    Plans are stateful (``times`` caps, RNG streams): parse one per run.
    """

    def __init__(self, rules, seed: int = 0, spec: str = ""):
        self.rules = list(rules)
        self.seed = seed
        self.spec = spec
        # Fault sites run on IO-pool worker threads too (native decode,
        # streamed chunk loads): the match→roll sequence mutates rule state
        # (fire caps, RNG draws) and must be atomic or `times=` caps
        # overshoot and the seeded fire sequence stops being deterministic.
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        rules = []
        for i, raw in enumerate(t for t in spec.split(",") if t.strip()):
            tokens = raw.strip().split(":")
            if len(tokens) < 2:
                raise ValueError(
                    f"bad fault rule {raw!r}: want scope:action[:k=v...] "
                    "or scope:k=v[...]"
                )
            # The site name is every leading token that is not a ``k=v``
            # parameter: one token (``preempt:iter=2``), the common two
            # (``io:read:p=0.3``), or three (``online:refresh:kill:iter=0``).
            # A 3+-token site must be REGISTERED — otherwise a mistyped
            # parameter (``io:read:oops``) would silently become part of a
            # site name nothing ever consumes.
            end = 1
            while end < len(tokens) and "=" not in tokens[end]:
                end += 1
            site = ":".join(t.strip() for t in tokens[:end])
            if end > 2 and site not in KNOWN_FAULT_SITES:
                raise ValueError(
                    f"bad fault param {tokens[2]!r} in rule {raw!r} "
                    "(want k=v)"
                )
            param_tokens = tokens[end:]
            params = {}
            for tok in param_tokens:
                k, sep, v = tok.partition("=")
                if not sep:
                    raise ValueError(
                        f"bad fault param {tok!r} in rule {raw!r} (want k=v)"
                    )
                params[k.strip()] = v.strip()
            rules.append(
                FaultRule(site, params, random.Random(f"{seed}:{site}:{i}"))
            )
        return cls(rules, seed=seed, spec=spec)

    def consume(self, site: str, **ctx) -> Optional[FaultRule]:
        """The first matching rule that fires for this call, else None."""
        with self._lock:
            for rule in self.rules:
                if rule.matches(site, ctx) and rule.roll():
                    return rule
            return None


# -- active-plan management --------------------------------------------------
#
# One process-wide plan: drivers install from --faults, tests via set_plan,
# and the env var PHOTON_FAULTS covers subprocesses (the plan re-parses only
# when the spec string changes, so the per-call cost with no plan is one
# os.environ.get).

_ENV_VAR = "PHOTON_FAULTS"
_SEED_VAR = "PHOTON_FAULTS_SEED"
_active: Optional[FaultPlan] = None
_env_cache: tuple = ("", 0, None)


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Install (or clear, with None) the process-wide fault plan.  An
    installed plan takes precedence over ``PHOTON_FAULTS``."""
    global _active
    _active = plan


def reset_env_plan() -> None:
    """Drop the cached env-var plan so the next :func:`active_plan` call
    re-parses ``PHOTON_FAULTS`` with fresh rule state (fire caps, RNG
    streams).  Drivers call this at run start: an env plan is scoped per
    run, not per process lifetime."""
    global _env_cache
    _env_cache = ("", 0, None)


def install_from_args(args) -> None:
    """Driver hook: ``--faults SPEC`` (with ``--faults-seed``) overrides the
    env var for this process; without the flag, any env-var plan restarts
    fresh for this run."""
    spec = getattr(args, "faults", None)
    if spec:
        set_plan(FaultPlan.parse(spec, seed=getattr(args, "faults_seed", 0)))
    else:
        reset_env_plan()


def active_plan() -> Optional[FaultPlan]:
    global _env_cache
    if _active is not None:
        return _active
    spec = os.environ.get(_ENV_VAR, "").strip()
    if not spec:
        return None
    seed = int(os.environ.get(_SEED_VAR, "0") or "0")
    if _env_cache[0] != spec or _env_cache[1] != seed:
        _env_cache = (spec, seed, FaultPlan.parse(spec, seed=seed))
    return _env_cache[2]


def fault_point(site: str, **ctx) -> None:
    """Declare an injectable fault site.  No-op without an active plan;
    raises the site's error type when a rule fires.

    ``io:*`` and ``checkpoint:read`` sites raise :class:`InjectedIOError`
    (retriable); ``*:kill``, ``checkpoint:write``, and ``checkpoint:stage``
    raise :class:`InjectedKillError` (fatal — the atomic-write/
    checkpoint-resume machinery, not a retry loop, must absorb these).
    """
    plan = active_plan()
    if plan is None:
        return
    rule = plan.consume(site, **ctx)
    if rule is None:
        return
    scope, _, action = site.partition(":")
    if action.endswith(("kill", "crash")) or site in ("checkpoint:write",
                                                      "checkpoint:stage"):
        raise InjectedKillError(f"injected kill at {site} ({ctx or rule.params})")
    raise InjectedIOError(f"injected IO fault at {site} ({ctx or rule.params})")


def consume_nan_injection(coordinate: Optional[str]) -> bool:
    """True when the plan wants this coordinate's next solve corrupted with
    NaNs (``solve:nan:coord=<name>``); consumes one fire."""
    plan = active_plan()
    if plan is None or coordinate is None:
        return False
    return plan.consume("solve:nan", coordinate=coordinate) is not None


def consume_hang_injection(replica: Optional[str]) -> bool:
    """True when the plan wants this serving replica's path to WEDGE (site
    ``replica:hang:replica=<id>`` — the probe-timeout leg): the consumer
    simulates the hang (a wedged batch, a sleeping child) instead of
    raising, so detection has to come from the supervisor's probe deadline
    exactly as it would for a real hang; consumes one fire."""
    plan = active_plan()
    if plan is None or replica is None:
        return False
    return plan.consume("replica:hang", replica=str(replica)) is not None
