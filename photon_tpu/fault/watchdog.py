"""Run watchdog: stall detection for iteration and IO progress.

The retry layer (PR 4) handles IO that FAILS; nothing handled IO that
HANGS — a wedged NFS read or a stuck container open blocks its thread
forever and the run looks "busy" while doing nothing.  This module closes
the retry/timeout/backoff triangle:

- **Heartbeats.**  Progress points call :func:`heartbeat(name)` — the
  descent loop once per outer iteration, the streamed L-BFGS loop once per
  host iteration, :func:`~photon_tpu.fault.retry.retry_call` once per IO
  attempt.  A heartbeat is one monotonic-clock store; the hot loops pay
  nanoseconds.
- **The watchdog thread** (:class:`Watchdog`, started by the drivers when
  ``--stall-timeout`` > 0) polls the heartbeat table and, when a site's
  age exceeds the stall timeout, emits ``watchdog.stalled{site=...}``
  telemetry and a log line — once per stall episode, again only after the
  site recovers and stalls anew.  The run report then says WHERE a hung
  run stopped making progress, instead of requiring a py-spy autopsy.
- **Escalation.**  With a stall timeout configured, guarded IO calls run
  under :func:`call_with_timeout`: the call executes on a daemon worker
  thread and a hang longer than the timeout raises
  :class:`IOStallTimeoutError` — an ``OSError``, so the retry layer treats
  a hung call exactly like a failed one (backoff, ``io.retries``, fresh
  attempt).  The abandoned worker thread is daemonic and unblocks (or
  leaks) in the background; that is the honest trade for progress — Python
  cannot safely interrupt a thread stuck in a C-level read.

Configuration: ``--stall-timeout SECONDS`` on every driver (0 disables,
the default), or ``PHOTON_STALL_TIMEOUT_S`` process-wide.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional, TypeVar

from photon_tpu.telemetry import NULL_SESSION

T = TypeVar("T")


class IOStallTimeoutError(OSError):
    """A guarded IO call exceeded the stall timeout.  An ``OSError`` on
    purpose: the retry layer's backoff-and-reattempt policy applies to a
    hung call exactly as to a failed one."""


# -- heartbeat table ---------------------------------------------------------
#
# One process-wide table: {site: last-progress monotonic time}.  Writers are
# the training loops and retry_call (including IO-pool worker threads); the
# reader is the watchdog thread.  Every access takes the lock — a
# first-time-site insert during the reader's iteration would otherwise be a
# "dictionary changed size during iteration" crash on the watchdog thread.

_beats: Dict[str, float] = {}
_beats_lock = threading.Lock()
_stall_timeout_override: Optional[float] = None


def heartbeat(name: str) -> None:
    """Record progress for ``name`` (cheap: one clock read + locked dict
    store)."""
    with _beats_lock:
        _beats[name] = time.monotonic()


def complete(name: str) -> None:
    """Retire ``name`` from the heartbeat table: the activity FINISHED —
    silence from a finished site is not a stall.  The loops call this when
    they exit and retry_call when an attempt sequence ends, so a healthy
    run never false-alarms during later phases that simply don't touch the
    site anymore."""
    with _beats_lock:
        _beats.pop(name, None)


def progress_ages() -> Dict[str, float]:
    """Seconds since each LIVE site's last heartbeat (a snapshot)."""
    now = time.monotonic()
    with _beats_lock:
        return {name: now - t for name, t in _beats.items()}


def age_of(name: str) -> Optional[float]:
    """Seconds since ``name``'s last heartbeat, or None when the site has
    no live heartbeat (never marked, or retired by :func:`complete`).  The
    fleet supervisor's hang check reads single replica sites through this
    instead of snapshotting the whole table every probe."""
    with _beats_lock:
        t = _beats.get(name)
    return None if t is None else time.monotonic() - t


def clear_heartbeats() -> None:
    """Drop all recorded heartbeats (run scoped: a finished run's stale
    sites must not look stalled to the next run's watchdog)."""
    with _beats_lock:
        _beats.clear()


def set_stall_timeout(seconds: Optional[float]) -> None:
    """Install (or clear, with None) the run-scoped stall timeout — the
    driver flag's value; overrides ``PHOTON_STALL_TIMEOUT_S``."""
    global _stall_timeout_override
    _stall_timeout_override = seconds


def stall_timeout() -> float:
    """The operative stall timeout in seconds (0 = disabled): the driver
    flag when installed, else ``PHOTON_STALL_TIMEOUT_S``, else 0."""
    if _stall_timeout_override is not None:
        return max(0.0, float(_stall_timeout_override))
    raw = os.environ.get("PHOTON_STALL_TIMEOUT_S", "").strip()
    try:
        return max(0.0, float(raw)) if raw else 0.0
    except ValueError:
        return 0.0


# -- escalation --------------------------------------------------------------


def call_with_timeout(fn: Callable[[], T], timeout_s: float,
                      site: str = "io") -> T:
    """Run ``fn()`` on a daemon worker thread; raise
    :class:`IOStallTimeoutError` if it has not finished within
    ``timeout_s``.  ``timeout_s <= 0`` calls ``fn`` inline (no thread)."""
    if timeout_s <= 0:
        return fn()
    box: dict = {}
    done = threading.Event()

    def run():
        try:
            box["value"] = fn()
        except BaseException as e:  # re-raised on the caller thread below
            box["error"] = e
        finally:
            done.set()

    worker = threading.Thread(
        target=run, name=f"io-guard-{site}", daemon=True
    )
    worker.start()
    if not done.wait(timeout_s):
        # The worker stays parked on the hung call (daemonic, abandoned);
        # the caller gets a retriable timeout and a FRESH attempt.
        raise IOStallTimeoutError(
            f"guarded IO at {site!r} made no progress for {timeout_s:g}s"
        )
    if "error" in box:
        raise box["error"]
    return box["value"]


# -- the watchdog thread -----------------------------------------------------


class Watchdog:
    """Background thread that turns missing heartbeats into telemetry.

    Polls the heartbeat table every ``poll_interval_s`` (default: a quarter
    of the stall timeout, floored at 0.05s) and, when a site's age crosses
    ``stall_timeout_s``, increments ``watchdog.stalled{site=...}`` and sets
    the ``watchdog.stall_age_seconds{site=...}`` gauge — once per stall
    episode (the gauge keeps updating while the stall lasts; the counter
    fires again only after the site makes progress and stalls anew).
    """

    def __init__(self, stall_timeout_s: float, telemetry=None, logger=None,
                 poll_interval_s: Optional[float] = None):
        if stall_timeout_s <= 0:
            raise ValueError("Watchdog needs stall_timeout_s > 0")
        self.stall_timeout_s = float(stall_timeout_s)
        self.telemetry = telemetry or NULL_SESSION
        self.logger = logger
        self.poll_interval_s = (
            max(0.05, self.stall_timeout_s / 4.0)
            if poll_interval_s is None else poll_interval_s
        )
        self._stalled: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # One pass over the heartbeat table (extracted so tests can drive the
    # detection logic without timing a real thread).
    def check_once(self) -> list:
        newly_stalled = []
        ages = progress_ages()
        self._stalled &= set(ages)  # retired sites leave the episode set
        for name, age in ages.items():
            if age > self.stall_timeout_s:
                self.telemetry.gauge(
                    "watchdog.stall_age_seconds", site=name
                ).set(age)
                if name not in self._stalled:
                    self._stalled.add(name)
                    self.telemetry.counter(
                        "watchdog.stalled", site=name
                    ).inc()
                    newly_stalled.append(name)
                    if self.logger is not None:
                        self.logger.warning(
                            "watchdog: %s made no progress for %.1fs "
                            "(stall timeout %.1fs)", name, age,
                            self.stall_timeout_s,
                        )
            else:
                self._stalled.discard(name)
        return newly_stalled

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — detection must outlive a
                # bad poll (a telemetry hiccup must not silently kill
                # stall detection for the rest of the run).
                pass

    def start(self) -> "Watchdog":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="photon-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
